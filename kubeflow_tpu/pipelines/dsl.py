"""Pipeline DSL — the KFP v2 authoring surface (⟨pipelines: sdk/python/kfp —
dsl⟩, SURVEY.md §2.4/§3.5).

`@component` wraps a self-contained Python function; `@pipeline` wraps a
function that calls components to build a DAG. `compile_pipeline()` emits
the IR (the PipelineSpec-proto analog, here plain JSON) that the C++
pipeline controller executes. Artifacts flow by path: a component declares
`InputArtifact` / `OutputArtifact` parameters, the launcher hands it real
filesystem paths at run time.

    @component
    def preprocess(out: OutputArtifact, n: int = 100):
        ...write files under `out`...

    @component
    def train(data: InputArtifact, model: OutputArtifact, lr: float = 0.1):
        ...

    @pipeline
    def demo(n: int = 100, lr: float = 0.1):
        p = preprocess(n=n)
        train(data=p.output("out"), lr=lr)

    ir = compile_pipeline(demo)
"""

from __future__ import annotations

import inspect
import textwrap
import threading
import typing
from typing import Any, Callable


class PipelineError(ValueError):
    pass


class InputArtifact:
    """Annotation marker: parameter receives the path of an upstream
    artifact."""


class OutputArtifact:
    """Annotation marker: parameter receives a fresh directory path the
    component must populate."""


_PARAM_TYPES = {int: "int", float: "double", str: "string", bool: "bool",
                list: "json", dict: "json"}


class ParamRef:
    """Reference to a pipeline-level parameter."""

    def __init__(self, name: str):
        self.name = name


class OutputRef:
    """Reference to a task's output artifact."""

    def __init__(self, task: "Task", output: str):
        self.task = task
        self.output = output


class ResultRef:
    """Reference to a task's returned value (its output parameter)."""

    def __init__(self, task: "Task"):
        self.task = task


class LoopVar:
    """The per-iteration item inside a ParallelFor block. Scalar items
    substitute directly; dict items support chained `item.key` /
    `item["key"]` access (each hop appends to the lookup path)."""

    def __init__(self, loop: "ParallelFor", path: tuple[str, ...] = ()):
        self._loop = loop
        self._path = path

    def __getattr__(self, key: str) -> "LoopVar":
        if key.startswith("_"):
            raise AttributeError(key)
        return LoopVar(self._loop, self._path + (key,))

    def __getitem__(self, key: str) -> "LoopVar":
        return LoopVar(self._loop, self._path + (key,))

    def _value(self, item: Any) -> Any:
        v = item
        for key in self._path:
            if not isinstance(v, dict) or key not in v:
                raise PipelineError(
                    f"ParallelFor item {item!r} has no key path "
                    f"{'.'.join(self._path)!r}")
            v = v[key]
        return v


class Collected:
    """Fan-in marker: the matching outputs of every ParallelFor iteration.
    Wraps `task.output(...)` (collected artifact paths — the component
    receives a directory of numbered symlinks) or `task.result` (collected
    values — the component receives a JSON list param)."""

    def __init__(self, ref: OutputRef | ResultRef):
        if not isinstance(ref, (OutputRef, ResultRef)):
            raise PipelineError(
                "Collected() wraps task.output(...) or task.result")
        self.ref = ref


class Task:
    def __init__(self, name: str, component: "Component",
                 arguments: dict[str, Any]):
        self.name = name
        self.component = component
        self.arguments = arguments
        self.after: list[Task] = []
        self.when: list[dict] = []      # conjunction of condition clauses
        self.exit_scope: list[str] | None = None  # names guarded (exit task)

    def output(self, name: str) -> OutputRef:
        if name not in self.component.outputs:
            raise PipelineError(
                f"component {self.component.name!r} has no output {name!r}; "
                f"declared outputs: {self.component.outputs}")
        return OutputRef(self, name)

    @property
    def outputs(self) -> dict[str, OutputRef]:
        return {o: OutputRef(self, o) for o in self.component.outputs}

    @property
    def result(self) -> ResultRef:
        """The component function's return value (declare it with a return
        annotation: `def f(...) -> float`)."""
        if not self.component.returns:
            raise PipelineError(
                f"component {self.component.name!r} returns nothing; add a "
                f"return annotation (-> int/float/str/bool) to use .result")
        return ResultRef(self)

    def after_task(self, *tasks: "Task") -> "Task":
        """Explicit ordering edge with no data dependency (dsl .after())."""
        self.after.extend(tasks)
        return self


class _PipelineContext(threading.local):
    def __init__(self):
        self.tasks: list[Task] | None = None
        # id(original in-loop Task) -> unrolled clones, for Collected().
        self.expansions: dict[int, list[Task]] = {}


_ctx = _PipelineContext()


class Component:
    """A packaged python-function step (KFP lightweight component), or a
    raw-command step when built via `container_component` (KFP container
    component analog)."""

    def __init__(self, fn: Callable | None, replicas: int = 1,
                 cpu_devices_per_proc: int = 0, cache: bool = True,
                 retries: int = 0, devices_per_proc: int = 1,
                 num_slices: int = 1):
        self.fn = fn
        self.replicas = replicas
        self.cpu_devices_per_proc = cpu_devices_per_proc
        # TPU placement (the kfp-kubernetes nodeSelector/`google.com/tpu`
        # analog, SURVEY.md §2.4): chips per process and slice count for
        # the gang the controller materializes for this step.
        self.devices_per_proc = int(devices_per_proc)
        self.num_slices = int(num_slices)
        self.cache = cache
        self.retries = int(retries)
        self.kind = "python"
        self.argv: list[str] = []
        self.params: dict[str, str] = {}      # name -> type
        self.defaults: dict[str, Any] = {}
        self.inputs: list[str] = []           # InputArtifact params
        self.outputs: list[str] = []          # OutputArtifact params
        self.returns: str | None = None       # return-annotation type
        if fn is None:       # container_component fills the fields itself
            self.name = ""
            self.source = ""
            return
        self.name = fn.__name__
        try:
            self.source = textwrap.dedent(inspect.getsource(fn))
        except OSError:
            # No retrievable source (REPL, or the launcher re-exec'ing a
            # packaged component). Such a Component can run but not be
            # re-compiled into IR — to_ir() enforces that.
            self.source = ""

        # get_type_hints resolves string annotations (files using
        # `from __future__ import annotations`) against fn's globals.
        try:
            hints = typing.get_type_hints(fn)
        except Exception:
            hints = {}
        sig = inspect.signature(fn)
        for pname, p in sig.parameters.items():
            ann = hints.get(pname, p.annotation)
            if ann is InputArtifact:
                self.inputs.append(pname)
            elif ann is OutputArtifact:
                self.outputs.append(pname)
            elif ann in _PARAM_TYPES:
                self.params[pname] = _PARAM_TYPES[ann]
                if p.default is not inspect.Parameter.empty:
                    self.defaults[pname] = p.default
            else:
                raise PipelineError(
                    f"component {self.name!r} parameter {pname!r} needs an "
                    f"annotation: int/float/str/bool/list/dict, "
                    f"InputArtifact, or OutputArtifact")
        ret = hints.get("return", sig.return_annotation)
        if ret in _PARAM_TYPES:
            # The function's return value becomes its output parameter
            # (KFP's NamedTuple/scalar outputs), usable in dsl.Condition
            # and Collected fan-in via task.result.
            self.returns = _PARAM_TYPES[ret]

    def __call__(self, **arguments: Any) -> Task:
        if _ctx.tasks is None:
            raise PipelineError(
                f"component {self.name!r} called outside a @pipeline "
                f"function")
        for k, v in arguments.items():
            if k in self.inputs:
                if isinstance(v, Collected):
                    if not isinstance(v.ref, OutputRef):
                        raise PipelineError(
                            f"{self.name}.{k} is an InputArtifact; Collected "
                            f"must wrap task.output(...), not task.result")
                elif not isinstance(v, OutputRef):
                    raise PipelineError(
                        f"{self.name}.{k} is an InputArtifact; pass "
                        f"task.output(...)")
            elif k in self.params:
                if isinstance(v, Collected):
                    if not isinstance(v.ref, ResultRef):
                        raise PipelineError(
                            f"{self.name}.{k} is a parameter; Collected "
                            f"artifacts go to an InputArtifact")
                    if self.params[k] != "json":
                        raise PipelineError(
                            f"{self.name}.{k} receives Collected results; "
                            f"annotate it as `list`")
                elif isinstance(v, OutputRef):
                    raise PipelineError(
                        f"{self.name}.{k} is a parameter; got an artifact")
            elif k in self.outputs:
                raise PipelineError(
                    f"{self.name}.{k} is an OutputArtifact; it is produced, "
                    f"not passed")
            else:
                raise PipelineError(
                    f"component {self.name!r} has no parameter {k!r}")
        missing = [i for i in self.inputs if i not in arguments]
        if missing:
            raise PipelineError(
                f"component {self.name!r} missing input artifacts: {missing}")
        # Required params (no default) must be bound now — catching this at
        # compile time beats burning a gang on a TypeError in the launcher.
        unbound = [p for p in self.params
                   if p not in arguments and p not in self.defaults]
        if unbound:
            raise PipelineError(
                f"component {self.name!r} missing required params: {unbound}")
        # Unique task name within the pipeline: name, name-2, name-3, ...
        base = self.name
        existing = {t.name for t in _ctx.tasks}
        name, i = base, 1
        while name in existing:
            i += 1
            name = f"{base}-{i}"
        task = Task(name, self, arguments)
        _ctx.tasks.append(task)
        return task

    def to_ir(self) -> dict:
        if self.kind == "python" and not self.source:
            raise PipelineError(
                f"component {self.name!r} has no retrievable source (was it "
                f"defined in a REPL?); define it in a file")
        return {
            "name": self.name,
            "kind": self.kind,
            "source": self.source,
            "argv": list(self.argv),
            "params": dict(self.params),
            "defaults": dict(self.defaults),
            "inputs": list(self.inputs),
            "outputs": list(self.outputs),
            "replicas": self.replicas,
            "cpu_devices_per_proc": self.cpu_devices_per_proc,
            "devices_per_proc": self.devices_per_proc,
            "num_slices": self.num_slices,
            "cache": self.cache,
            "retries": self.retries,
            "returns": self.returns,
        }


def component(fn: Callable | None = None, *, replicas: int = 1,
              cpu_devices_per_proc: int = 0, cache: bool = True,
              retries: int = 0, devices_per_proc: int = 1,
              num_slices: int = 1):
    """Decorator: python function → Component (KFP @dsl.component).
    `retries` is the per-task retry budget (KFP set_retry): the controller
    relaunches a failed attempt up to that many times before the task — and
    with it the run — fails. `devices_per_proc`/`num_slices` place the
    step's gang on TPU topology (the kfp-kubernetes TPU-resource analog)."""
    def wrap(f: Callable) -> Component:
        return Component(f, replicas=replicas,
                         cpu_devices_per_proc=cpu_devices_per_proc,
                         cache=cache, retries=retries,
                         devices_per_proc=devices_per_proc,
                         num_slices=num_slices)
    return wrap(fn) if fn is not None else wrap


def container_component(name: str, argv: list[str], *,
                        params: dict[str, type] | None = None,
                        defaults: dict[str, Any] | None = None,
                        inputs: list[str] | None = None,
                        outputs: list[str] | None = None,
                        cache: bool = True, retries: int = 0,
                        replicas: int = 1, devices_per_proc: int = 1,
                        num_slices: int = 1) -> Component:
    """Raw-command step. `argv` may use `{{params.x}}`, `{{inputs.a}}`,
    `{{outputs.b}}` placeholders, resolved by the launcher at run time."""
    c = Component(None, cache=cache, retries=retries, replicas=replicas,
                  devices_per_proc=devices_per_proc, num_slices=num_slices)
    c.kind = "command"
    c.name = name
    c.argv = list(argv)
    c.params = {k: _PARAM_TYPES[t] for k, t in (params or {}).items()}
    c.defaults = dict(defaults or {})
    c.inputs = list(inputs or [])
    c.outputs = list(outputs or [])
    return c


# -- control flow (KFP dsl.Condition / dsl.ParallelFor / dsl.ExitHandler) ----

_CONDITION_OPS = ("==", "!=", ">=", "<=", ">", "<")


def _check_operand(v: Any, what: str) -> None:
    if isinstance(v, (ParamRef, ResultRef, LoopVar)):
        return
    if isinstance(v, (int, float, str, bool)):
        return
    raise PipelineError(
        f"Condition {what} must be a literal, a pipeline param, or "
        f"task.result; got {v!r}")


class Condition:
    """`with Condition(task.result, ">", 0.5): ...` — tasks in the block
    run only when the comparison holds at scheduling time; otherwise they
    (and their dependents) are Skipped. Nested conditions AND together."""

    def __init__(self, lhs: Any, op: str, rhs: Any):
        if op not in _CONDITION_OPS:
            raise PipelineError(
                f"Condition op {op!r} not in {_CONDITION_OPS}")
        _check_operand(lhs, "lhs")
        _check_operand(rhs, "rhs")
        self.lhs, self.op, self.rhs = lhs, op, rhs

    def __enter__(self) -> "Condition":
        if _ctx.tasks is None:
            raise PipelineError("Condition used outside a @pipeline")
        self._start = len(_ctx.tasks)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc_type is not None:
            return False
        block = _ctx.tasks[self._start:]
        if not block:
            raise PipelineError("Condition block created no tasks")
        clause = {"lhs": self.lhs, "op": self.op, "rhs": self.rhs}
        for t in block:
            t.when.append(dict(clause))
        return False


class ParallelFor:
    """`with ParallelFor([a, b, c]) as item: comp(x=item)` — the block is
    traced once, then unrolled at compile time into one task set per item
    (items are static; the TPU-first stance is the same as for shapes:
    static fan-out compiles, dynamic fan-out re-plans). Fan-in afterwards
    with `Collected(t.output(...))` or `Collected(t.result)`."""

    def __init__(self, items: Any):
        items = list(items)
        if not items:
            raise PipelineError("ParallelFor needs at least one item")
        for it in items:
            if not isinstance(it, (int, float, str, bool, dict)):
                raise PipelineError(
                    f"ParallelFor items must be scalars or dicts, got "
                    f"{it!r}")
        self.items = items

    def __enter__(self) -> LoopVar:
        if _ctx.tasks is None:
            raise PipelineError("ParallelFor used outside a @pipeline")
        self._start = len(_ctx.tasks)
        return LoopVar(self)

    def _subst(self, v: Any, item: Any, mapping: dict[str, Task]) -> Any:
        if isinstance(v, LoopVar):
            if v._loop is not self:
                return v  # an outer loop's var; substituted at its unroll
            return v._value(item)
        if isinstance(v, OutputRef) and v.task.name in mapping:
            return OutputRef(mapping[v.task.name], v.output)
        if isinstance(v, ResultRef) and v.task.name in mapping:
            return ResultRef(mapping[v.task.name])
        if isinstance(v, Collected):
            raise PipelineError(
                "Collected() belongs after the ParallelFor block, not "
                "inside it")
        return v

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc_type is not None:
            return False
        block = _ctx.tasks[self._start:]
        if not block:
            raise PipelineError("ParallelFor block created no tasks")
        del _ctx.tasks[self._start:]
        expansions: dict[str, list[Task]] = {t.name: [] for t in block}
        block_names = {t.name for t in block}
        existing = {t.name for t in _ctx.tasks}
        mappings: list[dict[str, Task]] = []
        for i, item in enumerate(self.items):
            mapping = {t.name: Task(f"{t.name}-it{i}", t.component, {})
                       for t in block}
            mappings.append(mapping)
            for t in block:
                clone = mapping[t.name]
                if clone.name in existing:
                    raise PipelineError(
                        f"task name collision unrolling ParallelFor: "
                        f"{clone.name!r}")
                existing.add(clone.name)
                clone.arguments = {
                    k: self._subst(v, item, mapping)
                    for k, v in t.arguments.items()}
                clone.after = [mapping.get(a.name, a) for a in t.after]
                clone.when = [
                    {"lhs": self._subst(c["lhs"], item, mapping),
                     "op": c["op"],
                     "rhs": self._subst(c["rhs"], item, mapping)}
                    for c in t.when]
                _ctx.tasks.append(clone)
                expansions[t.name].append(clone)
        # Inner-loop expansions whose clones this unroll just replaced must
        # follow to the new per-iteration clones, so a Collected() over a
        # nested ParallelFor task fans in across BOTH loops.
        for key, clones in list(_ctx.expansions.items()):
            if any(c.name in block_names for c in clones):
                _ctx.expansions[key] = [
                    m[c.name] for m in mappings for c in clones
                    if c.name in m]
        for t in block:
            _ctx.expansions[id(t)] = expansions[t.name]
        return False


class ExitHandler:
    """`with ExitHandler(cleanup(...)): ...` — the exit task runs once
    every task in the block is terminal, whether the block succeeded or
    failed (KFP dsl.ExitHandler / Argo exit handler)."""

    def __init__(self, exit_task: Task):
        if not isinstance(exit_task, Task):
            raise PipelineError(
                "ExitHandler takes an already-created task, e.g. "
                "ExitHandler(cleanup(msg='done'))")
        for v in exit_task.arguments.values():
            if isinstance(v, (OutputRef, ResultRef, Collected)):
                raise PipelineError(
                    "an exit task can only take literals or pipeline "
                    "params — it must be runnable even when the block "
                    "fails")
        self.exit_task = exit_task

    def __enter__(self) -> "ExitHandler":
        if _ctx.tasks is None:
            raise PipelineError("ExitHandler used outside a @pipeline")
        self._start = len(_ctx.tasks)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc_type is not None:
            return False
        block = [t for t in _ctx.tasks[self._start:] if t is not self.exit_task]
        if not block:
            raise PipelineError("ExitHandler block created no tasks")
        self.exit_task.exit_scope = [t.name for t in block]
        return False


class Pipeline:
    def __init__(self, fn: Callable):
        self.fn = fn
        self.name = fn.__name__
        self.params: dict[str, Any] = {}
        try:  # resolve PEP-563 string annotations like Component does
            hints = typing.get_type_hints(fn)
        except Exception:
            hints = {}
        sig = inspect.signature(fn)
        for pname, p in sig.parameters.items():
            if hints.get(pname, p.annotation) not in _PARAM_TYPES:
                raise PipelineError(
                    f"pipeline {self.name!r} parameter {pname!r} needs an "
                    f"int/float/str/bool annotation")
            self.params[pname] = (None if p.default is
                                  inspect.Parameter.empty else p.default)


def pipeline(fn: Callable) -> Pipeline:
    """Decorator: DAG-building function → Pipeline (KFP @dsl.pipeline)."""
    return Pipeline(fn)


def _arg_ir(value: Any, final_names: set[str],
            expansions: dict[int, list[Task]]) -> dict:
    if isinstance(value, ParamRef):
        return {"param": value.name}
    if isinstance(value, OutputRef):
        if value.task.name not in final_names:
            raise PipelineError(
                f"output of in-loop task {value.task.name!r} referenced "
                f"outside its ParallelFor; wrap it in Collected(...)")
        return {"task": value.task.name, "output": value.output}
    if isinstance(value, ResultRef):
        if value.task.name not in final_names:
            raise PipelineError(
                f"result of in-loop task {value.task.name!r} referenced "
                f"outside its ParallelFor; wrap it in Collected(...)")
        return {"task": value.task.name, "result": True}
    if isinstance(value, Collected):
        clones = expansions.get(id(value.ref.task))
        if not clones:
            raise PipelineError(
                "Collected() must wrap a task created inside a ParallelFor "
                "block")
        if isinstance(value.ref, OutputRef):
            out = value.ref.output
            return {"collect": [{"task": c.name, "output": out}
                                for c in clones]}
        return {"collect": [{"task": c.name, "result": True}
                            for c in clones]}
    if isinstance(value, (int, float, str, bool, list, dict)):
        return {"value": value}
    raise PipelineError(f"unsupported argument value: {value!r}")


def compile_pipeline(p: Pipeline, **param_overrides: Any) -> dict:
    """Traces the pipeline function and emits the IR document.

    The KFP compiler analog (⟨pipelines: sdk/python/kfp/compiler⟩): tasks
    carry their full component spec (self-contained IR — no registry
    lookups at run time), arguments reference literals, pipeline params, or
    upstream outputs; `depends_on` holds explicit .after() edges (data
    edges are implied by arguments and recomputed by the controller).
    """
    params = dict(p.params)
    for k, v in param_overrides.items():
        if k not in params:
            raise PipelineError(f"pipeline {p.name!r} has no param {k!r}")
        params[k] = v
    missing = [k for k, v in params.items() if v is None]
    if missing:
        raise PipelineError(
            f"pipeline {p.name!r} params need values: {missing}")

    if _ctx.tasks is not None:
        raise PipelineError("nested pipeline compilation is not supported")
    _ctx.tasks = []
    _ctx.expansions = {}
    try:
        p.fn(**{k: ParamRef(k) for k in params})
        tasks = _ctx.tasks
        expansions = _ctx.expansions
    finally:
        _ctx.tasks = None
        _ctx.expansions = {}

    if not tasks:
        raise PipelineError(f"pipeline {p.name!r} has no tasks")

    final_names = {t.name for t in tasks}
    ir_tasks: dict[str, dict] = {}
    for t in tasks:
        args = {k: _arg_ir(v, final_names, expansions)
                for k, v in t.arguments.items()}
        # Unpassed params fall back to component defaults at launch time.
        entry = {
            "component": t.component.to_ir(),
            "arguments": args,
            "depends_on": sorted({a.name for a in t.after}),
        }
        if t.when:
            entry["when"] = [
                {"lhs": _arg_ir(c["lhs"], final_names, expansions),
                 "op": c["op"],
                 "rhs": _arg_ir(c["rhs"], final_names, expansions)}
                for c in t.when]
        if t.exit_scope is not None:
            if t.when:
                raise PipelineError(
                    f"exit task {t.name!r} cannot sit inside a Condition "
                    f"block — it must run unconditionally when its scope "
                    f"ends")
            missing = [s for s in t.exit_scope if s not in final_names]
            if missing:
                raise PipelineError(
                    f"exit handler scope references unrolled tasks "
                    f"{missing}; put the ParallelFor fully inside the "
                    f"ExitHandler block")
            entry["exit_handler"] = True
            entry["scope"] = list(t.exit_scope)
            # An exit task must actually run every time — never cache-skip.
            entry["component"]["cache"] = False
        ir_tasks[t.name] = entry
    return {
        "schema": "tpk-pipeline/v1",
        "name": p.name,
        "params": params,
        "tasks": ir_tasks,
    }
