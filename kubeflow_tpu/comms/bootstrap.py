"""Process bootstrap: the TPU-native replacement for rendezvous env plumbing.

The reference's controllers inject MASTER_ADDR/MASTER_PORT/WORLD_SIZE/RANK
(PyTorchJob), TF_CONFIG JSON (TFJob), or SSH hostfiles (MPIJob) and leave
rendezvous to torchrun/NCCL (SURVEY.md §2.7, §3.1). Here the contract is
three env vars consumed by `jax.distributed.initialize`, and the entire SSH/
hostfile/NCCL-unique-id plane is deleted — XLA compiles collectives onto
ICI/DCN directly:

    TPK_COORDINATOR   host:port of process 0's coordination service
    TPK_NUM_PROCS     total process count (one per TPU VM host)
    TPK_PROC_ID       this process's index

Optional slice topology (multi-slice jobs over DCN):
    TPK_NUM_SLICES    number of TPU slices (default 1)
    TPK_SLICE_ID      this process's slice index
"""

from __future__ import annotations

import dataclasses
import os


@dataclasses.dataclass(frozen=True)
class ProcessEnv:
    coordinator: str | None
    num_processes: int
    process_id: int
    num_slices: int = 1
    slice_id: int = 0

    @property
    def distributed(self) -> bool:
        return self.num_processes > 1


def read_env(environ=None) -> ProcessEnv:
    env = environ if environ is not None else os.environ
    coord = env.get("TPK_COORDINATOR")
    num = int(env.get("TPK_NUM_PROCS", "1"))
    pid = int(env.get("TPK_PROC_ID", "0"))
    if num > 1 and not coord:
        raise ValueError("TPK_NUM_PROCS > 1 requires TPK_COORDINATOR")
    if not 0 <= pid < num:
        raise ValueError(f"TPK_PROC_ID {pid} out of range [0, {num})")
    num_slices = int(env.get("TPK_NUM_SLICES", "1"))
    slice_id = int(env.get("TPK_SLICE_ID", "0"))
    if not 0 <= slice_id < num_slices:
        raise ValueError(
            f"TPK_SLICE_ID {slice_id} out of range [0, {num_slices})")
    return ProcessEnv(
        coordinator=coord, num_processes=num, process_id=pid,
        num_slices=num_slices, slice_id=slice_id)


_initialized = False


def initialize(penv: ProcessEnv | None = None) -> ProcessEnv:
    """Idempotent `jax.distributed.initialize` from the env contract.
    Single-process (num=1) skips initialization entirely — jit/collectives
    work locally, which is how unit tests and the 1-chip bench run."""
    global _initialized
    penv = penv or read_env()
    if penv.distributed and not _initialized:
        import jax

        jax.distributed.initialize(
            coordinator_address=penv.coordinator,
            num_processes=penv.num_processes,
            process_id=penv.process_id)
        _initialized = True
    return penv


def shutdown() -> None:
    global _initialized
    if _initialized:
        import jax

        jax.distributed.shutdown()
        _initialized = False
